"""distributedmnist_tpu/analysis: the runtime concurrency sanitizer
(ISSUE 8) — lock-order cycle detection, blocking-under-lock detection,
resource-balance accounting, and the bit-identical uninstalled path.

Every serve test runs under the sanitizer via the conftest autouse
fixture; THESE tests plant deliberate violations, so they manage their
own install/uninstall (the file name is not test_serve_*, which keeps
the autouse fixture out of the way)."""

import socket
import threading
import time

import numpy as np
import pytest

from distributedmnist_tpu.analysis import locks, sanitize

pytestmark = pytest.mark.analysis


@pytest.fixture
def san():
    s = sanitize.install_sanitizer()
    try:
        yield s
    finally:
        sanitize.uninstall_sanitizer()


# -- the uninstalled path --------------------------------------------------


def test_uninstalled_factories_are_bit_identical():
    """With no sanitizer, the factories return the BARE threading
    primitives — no wrapper objects exist, so production pays zero."""
    assert sanitize.active_sanitizer() is None
    assert type(locks.make_lock("x")) is type(threading.Lock())
    assert type(locks.make_rlock("x")) is type(threading.RLock())
    assert type(locks.make_semaphore("x", 2)) is threading.Semaphore
    cond = locks.make_condition("x")
    assert type(cond) is threading.Condition
    # default Condition: RLock-backed, exactly threading.Condition()
    assert type(cond._lock) is type(threading.RLock())
    t = locks.make_thread(target=lambda: None, name="t", daemon=True)
    assert type(t) is threading.Thread and t.daemon


def test_hooks_inert_without_sanitizer():
    # must be no-ops, not errors — this is the production hot path
    sanitize.blocking("anything")
    sanitize.resource_acquire("anything")
    sanitize.resource_release("anything")


def test_install_refuses_stacking_and_uninstall_restores_sleep(san):
    with pytest.raises(RuntimeError, match="already installed"):
        sanitize.install_sanitizer()
    patched = time.sleep
    sanitize.uninstall_sanitizer()
    assert time.sleep is not patched      # original restored
    # idempotent re-install works after uninstall
    s2 = sanitize.install_sanitizer()
    assert sanitize.active_sanitizer() is s2
    sanitize.uninstall_sanitizer()
    # fixture's uninstall tolerates being run twice
    sanitize.install_sanitizer()


# -- lock-order cycles -----------------------------------------------------


def test_ab_ba_cycle_detected_and_named(san):
    """The synthetic AB/BA deadlock: thread 1 takes A then B, thread 2
    takes B then A (sequentially, so the test itself cannot deadlock).
    The sanitizer must report a cycle naming both locks."""
    a = locks.make_lock("lock.A")
    b = locks.make_lock("lock.B")
    with a:
        with b:
            pass

    def ba():
        with b:
            with a:
                pass

    t = threading.Thread(target=ba)
    t.start()
    t.join()
    cycles = san.cycles()
    assert cycles, "AB/BA nesting produced no cycle finding"
    assert set(cycles[0]["cycle"]) == {"lock.A", "lock.B"}
    assert "lock.A" in cycles[0]["detail"]
    with pytest.raises(AssertionError, match="lock-order cycle"):
        san.assert_clean()


def test_transitive_cycle_detected(san):
    """A -> B on one thread, B -> C on another, C -> A on a third:
    no pair inverts, but the 3-cycle is still a deadlock."""
    a, b, c = (locks.make_lock(f"lock.{n}") for n in "ABC")

    def nest(outer, inner):
        with outer:
            with inner:
                pass

    for pair in ((a, b), (b, c), (c, a)):
        t = threading.Thread(target=nest, args=pair)
        t.start()
        t.join()
    assert san.cycles(), "3-lock transitive cycle missed"
    assert set(san.cycles()[0]["cycle"]) == {"lock.A", "lock.B", "lock.C"}


def test_same_name_nesting_flagged(san):
    """Two instances of one lock NAME nested on one thread: no order is
    defined within the class, so two threads nesting opposite instances
    would deadlock — flagged as a cycle."""
    l1 = locks.make_lock("engine.staging")
    l2 = locks.make_lock("engine.staging")
    with l1:
        with l2:
            pass
    assert san.cycles() and san.cycles()[0]["cycle"] == [
        "engine.staging", "engine.staging"]


def test_consistent_order_is_clean(san):
    """A -> B on many threads in ONE order: no finding."""
    a = locks.make_lock("lock.A")
    b = locks.make_lock("lock.B")

    def ab():
        with a:
            with b:
                pass

    threads = [threading.Thread(target=ab) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    ab()
    assert not san.cycles()
    san.assert_clean()


def test_rlock_reentry_is_not_a_cycle(san):
    """Re-entering one RLock instance is the same hold, not an edge
    (the registry admin lock's load_latest -> add path)."""
    r = locks.make_rlock("registry.admin", blocking_ok=True)
    with r:
        with r:
            assert san.held_locks() == ["registry.admin"]
    assert san.held_locks() == []
    assert not san.cycles()


# -- blocking under lock ---------------------------------------------------


def test_time_sleep_under_registry_state_lock_fires(san):
    """The ISSUE 8 contract case: a time.sleep held under a
    registry-state-shaped hot lock is the PR 3 bug class and must be
    reported with the lock named."""
    state = locks.make_lock("registry.state")
    with state:
        time.sleep(0.001)
    findings = san.blocking_findings()
    assert findings, "sleep under a hot lock produced no finding"
    assert findings[0]["locks"] == ["registry.state"]
    assert "time.sleep" in findings[0]["kind"]
    with pytest.raises(AssertionError, match="blocking under lock"):
        san.assert_clean()


def test_blocking_ok_lock_is_exempt(san):
    """Admin locks serialize slow work BY DESIGN (registry.admin,
    serve.admin): blocking under them is not a finding."""
    admin = locks.make_rlock("registry.admin", blocking_ok=True)
    with admin:
        time.sleep(0.001)
        sanitize.blocking("engine.fetch device->host sync")
    assert not san.blocking_findings()
    san.assert_clean()


def test_sleep_outside_locks_is_clean(san):
    time.sleep(0.001)
    assert not san.blocking_findings()


def test_socket_io_under_lock_fires(san):
    """Socket sends under a hot lock are the same class (an HTTP
    response written while holding server state would serialize every
    handler behind one slow client)."""
    a, b = socket.socketpair()
    try:
        hot = locks.make_lock("serve.state")
        with hot:
            a.sendall(b"x")
    finally:
        a.close()
        b.close()
    findings = san.blocking_findings()
    assert findings and findings[0]["kind"] == "socket.sendall"
    assert findings[0]["locks"] == ["serve.state"]


def test_declared_blocking_hook_fires(san):
    """The explicit blocking() weave (engine.fetch's device->host
    sync): flagged under a hot lock, silent otherwise."""
    sanitize.blocking("engine.fetch device->host sync")
    assert not san.blocking_findings()
    hot = locks.make_lock("batcher.queue")
    with hot:
        sanitize.blocking("engine.fetch device->host sync")
    assert san.blocking_findings()


# -- resource balance ------------------------------------------------------


def test_leaked_resource_fails_balance_check(san):
    """A checkout never recycled (the PR 5 staging-buffer leak class)
    must fail assert_clean with the resource named."""
    sanitize.resource_acquire("engine.staging")
    assert san.balances()["engine.staging"] == 1
    with pytest.raises(AssertionError,
                       match=r"engine.staging.*nets \+1"):
        san.assert_clean()
    sanitize.resource_release("engine.staging")
    san.assert_clean()


def test_release_without_acquire_is_flagged_immediately(san):
    sanitize.resource_release("engine.staging")
    errs = san.resource_errors()
    assert errs and errs[0]["resource"] == "engine.staging"
    assert errs[0]["balance"] == -1


def test_semaphore_holds_are_balance_checked(san):
    """make_semaphore doubles as the in-flight window's balance
    counter: held slots show in balances, a full cycle nets zero."""
    sem = locks.make_semaphore("batcher.inflight_slots", 2)
    sem.acquire()
    sem.acquire()
    assert san.balances()["batcher.inflight_slots"] == 2
    with pytest.raises(AssertionError, match="batcher.inflight_slots"):
        san.assert_clean()
    sem.release()
    sem.release()
    assert san.balances()["batcher.inflight_slots"] == 0
    san.assert_clean()


def test_engine_staging_leak_detected_end_to_end(san, eight_devices):
    """The real engine under the sanitizer: a dispatched-but-never-
    fetched batch is a leaked staging buffer (balance +1, assert_clean
    fails); fetching it recycles and the report goes clean. This is
    the exact invariant the conftest fixture asserts after every serve
    test."""
    import jax
    import jax.numpy as jnp

    from distributedmnist_tpu import models, optim
    from distributedmnist_tpu.parallel import make_mesh
    from distributedmnist_tpu.serve import InferenceEngine
    from distributedmnist_tpu.trainer import init_state

    mesh = make_mesh(eight_devices)
    model = models.build("mlp", platform="cpu")
    params = init_state(jax.random.PRNGKey(0), model,
                        optim.build("sgd", 0.1),
                        jnp.zeros((1, 28, 28, 1))).params
    eng = InferenceEngine(model, params, mesh, max_batch=8)
    x = np.zeros((3, 28, 28, 1), np.uint8)
    handle = eng.dispatch(x)          # checkout, deliberately unfetched
    assert san.balances()["engine.staging"] == 1
    with pytest.raises(AssertionError, match="engine.staging"):
        san.assert_clean()
    out = eng.fetch(handle)           # recycle closes the balance
    assert out.shape == (3, 10)
    assert san.balances()["engine.staging"] == 0
    # ... and a failing fetch still recycles (the PR 5 try/finally):
    from distributedmnist_tpu.serve import faults
    inj = faults.install(faults.FaultInjector.from_spec(
        "engine.fetch:p=1"))
    try:
        h2 = eng.dispatch(x)
        with pytest.raises(faults.InjectedFault):
            eng.fetch(h2)
    finally:
        faults.uninstall()
    assert san.balances()["engine.staging"] == 0
    # ... and a REAL backend error inside dispatch (after the staging
    # take — past where the failpoint fires) recycles on the error
    # path too: the dispatch-side twin of the fetch leak.
    real_forward = eng._forward
    def boom(params, x_dev):
        raise RuntimeError("device fell over")
    eng._forward = boom
    try:
        with pytest.raises(RuntimeError, match="device fell over"):
            eng.dispatch(x)
    finally:
        eng._forward = real_forward
    assert san.balances()["engine.staging"] == 0
    assert eng.infer(x).shape == (3, 10)   # pool healthy after the storm
    san.assert_clean()


# -- condition + thread plumbing ------------------------------------------


def test_sanitized_condition_is_reentrant_like_production(san):
    """Production threading.Condition() is RLock-backed; the sanitized
    one must match — a reentrant condition-lock path that works live
    must not silently deadlock under the test sanitizer (a hang with
    no finding is the one failure shape the sanitizer must never
    cause). wait() at depth 2 releases fully and restores depth."""
    cond = locks.make_condition("fleet.pick")
    woke = []

    def poker():
        with cond:
            woke.append(1)
            cond.notify_all()

    with cond:
        with cond:                      # reentrant hold, depth 2
            assert san.held_locks() == ["fleet.pick"]
            t = threading.Thread(target=poker)
            t.start()                   # can only acquire while we wait
            assert cond.wait(5.0), "reentrant wait never woke"
            t.join(timeout=5)
            assert woke
            # depth restored: still held after the wait
            assert san.held_locks() == ["fleet.pick"]
    assert san.held_locks() == []
    san.assert_clean()


def test_uninstall_preserves_later_sleep_patches(san):
    """uninstall must not clobber a patch another layer applied OVER
    the sanitizer's wrapper (pytest monkeypatch ordering): the later
    patch survives, and the orphaned wrapper underneath is inert."""
    calls = []
    wrapper = time.sleep                   # the sanitizer's patched sleep
    real = sanitize._patched_sleep[0]      # the captured original

    def stub(seconds):
        calls.append(seconds)

    time.sleep = stub                      # someone patches over us
    try:
        sanitize.uninstall_sanitizer()
        assert time.sleep is stub          # their patch survived
        time.sleep(1)                      # and works
        assert calls == [1]
        wrapper(0)                         # orphaned wrapper: inert
    finally:
        time.sleep = real                  # ground-truth restore
        sanitize.install_sanitizer()       # fixture teardown expects one


def test_sanitized_condition_wait_notify_roundtrip(san):
    """A producer/consumer handshake over make_condition works and
    leaves a clean report: wait() releases through the wrapper, so the
    held stack never lies across a wait."""
    cond = locks.make_condition("batcher.queue")
    box = []

    def consumer():
        with cond:
            while not box:
                cond.wait(1.0)

    t = threading.Thread(target=consumer)
    t.start()
    with cond:
        box.append(1)
        cond.notify_all()
    t.join(timeout=5)
    assert not t.is_alive()
    assert san.held_locks() == []
    san.assert_clean()


def test_env_var_install_coexists_with_pytest_fixtures():
    """DMNIST_SANITIZE=1 in the environment installs a process-global
    sanitizer at import — conftest must clear it so the per-test
    installs (serve autouse fixture, these tests' `san` fixture) don't
    refuse to stack and error every serve test at setup."""
    import os
    import subprocess
    import sys

    env = dict(os.environ, DMNIST_SANITIZE="1", JAX_PLATFORMS="cpu")
    target = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "test_serve_scheduler.py")
    r = subprocess.run(
        [sys.executable, "-m", "pytest", target, "-q", "-x",
         "-p", "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly"],
        capture_output=True, text=True, timeout=300, env=env)
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]


def test_make_thread_leak_reporting(san):
    """A registered non-daemon thread still alive shows in the leak
    report; joined threads do not."""
    gate = threading.Event()
    t = locks.make_thread(target=gate.wait, name="straggler",
                          daemon=False)
    t.start()
    assert [x.name for x in san.leaked_threads()] == ["straggler"]
    with pytest.raises(AssertionError, match="straggler"):
        san.assert_clean()
    gate.set()
    t.join(timeout=5)
    assert not san.leaked_threads()
    san.assert_clean()
